"""``mxtpu.amp`` — policy-driven bf16 autocast with f32 accumulation.

Reference: ``python/mxnet/contrib/amp/``† (MXNet v1.x automatic mixed
precision).  The reference hand-maintains FP16_FUNCS/FP32_FUNCS op
lists; here the op policy is *machine-derived* — PR 10's mxprec pass
classified every float-carrying HLO opcode across the six contract
targets into ``contracts/amp_policy.json`` (allow / deny / fp32_force /
inherit), and this module is the pass that consumes that file at trace
time.  Runtime behaviour and the committed evidence can never diverge:
an op is cast to bf16 only when its lowered jaxpr contains an
allow-class contraction opcode and nothing from the deny or fp32_force
classes.

How a cast decision is made (``_cast_decision``):

* only ops in :data:`ACCUM_READY` are candidates — the contraction ops
  whose implementations thread ``preferred_element_type=float32`` so
  bf16 inputs still accumulate in f32 (the policy's accumulation rule);
* the op's function is abstractly traced (``jax.make_jaxpr`` on the
  actual input avals + resolved params), its primitives mapped to HLO
  opcodes, and the decision is ``opcodes ⊆ allow`` — a deny-listed
  transcendental or fp32_force reduction anywhere inside vetoes the
  cast.  Decisions are cached per (op, avals, params) signature.

The transform itself is an interposition at the single eager/symbolic
dispatch choke point (``ndarray._invoke_op_inner``): inside an
:func:`autocast` scope, candidate ops have their f32 inputs cast to
bf16 *inside* the recorded function, so both jax AD and the eager
autograd tape differentiate through the casts.  Everything else —
transcendentals, reductions, collectives, elementwise glue — stays in
f32 because ``TrainStep``/``ModelRunner`` upcast every float parameter
to f32 at graph entry; the only sub-f32 values in the program are the
short bf16 edges feeding MXU contractions.  XLA folds the resulting
``convert(convert(w))`` chains at the weight edges.

Kill switch: ``MXTPU_AMP=0`` forces AMP off everywhere and the lowered
programs are bit-identical to pre-AMP behaviour (asserted by
``tests/test_amp.py``).  ``python -m mxtpu.amp --self-check`` probes
the policy parse, an autocast round-trip on the selftest program, and
the loss-scaler unit behaviour (wired as a ``tools/ci_static.py``
stage).
"""
from __future__ import annotations

import contextlib
import functools
import json
import os
from typing import Any, Dict, FrozenSet, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .. import knobs
from ..base import MXNetError

__all__ = [
    "POLICY_PATH", "load_policy", "policy_sets", "resolve",
    "scaler_config", "autocast", "active", "matmul_preferred",
    "wrap_op", "conv_general", "dot_general", "matmul",
    "scaler_init", "scaler_update",
    "all_finite", "self_check",
]

_REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
POLICY_PATH = os.path.join(_REPO_ROOT, "contracts", "amp_policy.json")

_BF16 = jnp.bfloat16
_F32 = jnp.float32
_SCALE_MAX = 2.0 ** 24


# ----------------------------------------------------------------------
# policy file
# ----------------------------------------------------------------------
@functools.lru_cache(maxsize=None)
def load_policy(path: Optional[str] = None) -> Dict[str, Any]:
    """Parse ``contracts/amp_policy.json`` (cached)."""
    p = path or POLICY_PATH
    try:
        with open(p, "r", encoding="utf-8") as f:
            policy = json.load(f)
    except (OSError, ValueError) as e:
        raise MXNetError(f"mxtpu.amp: cannot load AMP policy {p!r}: {e}")
    for key in ("allow", "deny", "fp32_force", "inherit"):
        if not isinstance(policy.get(key), dict):
            raise MXNetError(
                f"mxtpu.amp: policy {p!r} missing opcode class {key!r} "
                f"— regenerate with `python -m tools.mxprec --update`")
    return policy


@functools.lru_cache(maxsize=None)
def policy_sets(path: Optional[str] = None
                ) -> Tuple[FrozenSet[str], FrozenSet[str], FrozenSet[str]]:
    """(allow, deny, fp32_force) opcode sets from the policy file."""
    policy = load_policy(path)
    return (frozenset(policy["allow"]),
            frozenset(policy["deny"]),
            frozenset(policy["fp32_force"]))


def resolve(flag: Optional[bool] = None) -> bool:
    """Resolve the effective AMP switch: ``MXTPU_AMP=0`` kills it
    everywhere, ``MXTPU_AMP=1`` forces it on, otherwise the per-call
    ``amp=`` argument decides (default off)."""
    env = str(knobs.get("MXTPU_AMP")).strip().lower()
    if env in ("0", "off", "false", "no"):
        return False
    if flag is not None:
        return bool(flag)
    return env in ("1", "on", "true", "yes")


def scaler_config() -> Tuple[bool, float, int]:
    """(enabled, init_scale, grow_window) for the dynamic loss scaler.
    ``MXTPU_AMP_LOSS_SCALE=0`` disables scaling entirely."""
    init = float(knobs.get("MXTPU_AMP_LOSS_SCALE"))
    window = max(1, int(knobs.get("MXTPU_AMP_SCALE_WINDOW")))
    return init > 0.0, init, window


# ----------------------------------------------------------------------
# autocast scope (trace-time module globals — same zero-overhead-off
# shape as profiler._ACTIVE: one attribute read on the off path)
# ----------------------------------------------------------------------
_ACTIVE = False
_PREFERRED = None  # jnp.float32 while a scope is active


@contextlib.contextmanager
def autocast(enabled: bool = True):
    """Scope under which allow-listed contractions dispatched through
    the nd op registry run on bf16 inputs with f32 accumulation."""
    global _ACTIVE, _PREFERRED
    prev = (_ACTIVE, _PREFERRED)
    _ACTIVE, _PREFERRED = bool(enabled), (_F32 if enabled else None)
    try:
        yield
    finally:
        _ACTIVE, _PREFERRED = prev


def active() -> bool:
    return _ACTIVE


def matmul_preferred(*operands) -> Optional[Any]:
    """The ``preferred_element_type`` a contraction should request:
    f32 when an autocast scope is live and some float operand is
    sub-f32, else None (identical lowering to pre-AMP)."""
    if _PREFERRED is None:
        return None
    sub = False
    for a in operands:
        dt = getattr(a, "dtype", None)
        if dt is None or not jnp.issubdtype(dt, jnp.floating):
            return None
        if jnp.dtype(dt).itemsize < 4:
            sub = True
    return _PREFERRED if sub else None


# ----------------------------------------------------------------------
# cast classification
# ----------------------------------------------------------------------
# Contraction ops whose impls thread preferred_element_type=f32 so a
# bf16 cast keeps f32 accumulation.  Deconvolution is deliberately
# absent: lax.conv_transpose has no f32-accumulating VJP path here.
ACCUM_READY = frozenset({
    "dot", "batch_dot", "matmul", "linalg_gemm", "linalg_gemm2",
    "FullyConnected", "fully_connected",
    "Convolution", "convolution", "Convolution_v1",
})

# jax primitive -> pre-optimization HLO opcode, for the policy-class
# veto scan.  Structural/elementwise primitives are deliberately
# unmapped (the policy's `inherit` class); any *mapped* opcode outside
# the allow class vetoes the cast.
_PRIM_TO_HLO = {
    "dot_general": "dot",
    "conv_general_dilated": "convolution",
    "div": "divide",
    "exp": "exponential", "exp2": "exponential",
    "expm1": "exponential",
    "log": "log", "log1p": "log",
    "rsqrt": "rsqrt", "sqrt": "sqrt", "cbrt": "cbrt",
    "tanh": "tanh", "tan": "tan",
    "sin": "sine", "cos": "cosine", "atan2": "atan2",
    "erf": "erf", "erf_inv": "erf-inv", "logistic": "logistic",
    "pow": "power",
    "reduce_sum": "reduce", "reduce_prod": "reduce",
    "reduce_max": "reduce", "reduce_min": "reduce",
    "reduce_and": "reduce", "reduce_or": "reduce",
    "argmax": "reduce", "argmin": "reduce",
    "cumsum": "reduce-window", "cumprod": "reduce-window",
    "cummax": "reduce-window", "cummin": "reduce-window",
    "reduce_window_sum": "reduce-window",
    "reduce_window_max": "reduce-window",
    "reduce_window_min": "reduce-window",
    "psum": "all-reduce", "pmax": "all-reduce", "pmin": "all-reduce",
    "psum_scatter": "reduce-scatter",
    "all_gather": "all-gather",
    "all_to_all": "all-to-all",
}

_CAST_CACHE: Dict[Any, bool] = {}


def _sub_jaxprs(value):
    core = jax.core
    if isinstance(value, core.Jaxpr):
        yield value
    elif isinstance(value, core.ClosedJaxpr):
        yield value.jaxpr
    elif isinstance(value, (tuple, list)):
        for v in value:
            yield from _sub_jaxprs(v)


def _walk_opcodes(jaxpr, out: set) -> None:
    for eqn in jaxpr.eqns:
        prim = eqn.primitive.name
        if prim == "pallas_call":
            # kernel bodies are opaque custom calls; their precision
            # contract lives in the policy's custom_calls section
            out.add("custom-call")
            continue
        hlo = _PRIM_TO_HLO.get(prim)
        if hlo is not None:
            out.add(hlo)
        for v in eqn.params.values():
            for sub in _sub_jaxprs(v):
                _walk_opcodes(sub, out)


def _param_key(resolved: Dict[str, Any]) -> str:
    try:
        return repr(sorted(resolved.items(), key=lambda kv: kv[0]))
    except Exception:
        return "<unkeyable>"


def _cast_decision(name: str, op, arrays, resolved) -> bool:
    key = (name,
           tuple((tuple(a.shape), str(a.dtype)) for a in arrays),
           _param_key(resolved))
    hit = _CAST_CACHE.get(key)
    if hit is not None:
        return hit
    allow, deny, force = policy_sets()
    structs = [jax.ShapeDtypeStruct(a.shape, a.dtype) for a in arrays]
    try:
        closed = jax.make_jaxpr(
            lambda *xs: op.fn(*xs, **resolved))(*structs)
        opcodes: set = set()
        _walk_opcodes(closed.jaxpr, opcodes)
        # the policy drives the decision: cast only when the op lowers
        # to allow-class contractions and nothing deny/fp32_force-class
        decision = bool(opcodes) and opcodes <= allow
        assert not (opcodes & (deny | force)) or not decision
    except Exception:
        decision = False
    _CAST_CACHE[key] = decision
    return decision


def wrap_op(name: str, op, arrays, resolved):
    """Inside an autocast scope, return a replacement for ``op.fn``
    that casts f32 inputs to bf16 (f32 accumulation comes from the
    impl's preferred_element_type) — or None to leave the op alone.
    Called from ``ndarray._invoke_op_inner``."""
    if name not in ACCUM_READY:
        return None
    if not _cast_decision(name, op, arrays, resolved):
        return None

    def fn(*arrs):
        arrs = [a.astype(_BF16)
                if getattr(a, "dtype", None) == _F32 else a
                for a in arrs]
        return op.fn(*arrs, **resolved)
    return fn


# ----------------------------------------------------------------------
# bf16 convolution with f32 accumulation.  lax.conv_general_dilated's
# builtin transpose rule rejects a bf16-operand/f32-cotangent pair on
# this jax pin, so the f32-accumulating conv needs an explicit VJP: the
# cotangent is cast back to bf16 (the AMP gradient dtype) and both
# transpose convolutions again request f32 accumulation.
# ----------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5, 6))
def conv_general(x, w, strides, padding, rhs_dilation, dn, groups):
    return lax.conv_general_dilated(
        x, w, window_strides=strides, padding=padding,
        rhs_dilation=rhs_dilation, dimension_numbers=dn,
        feature_group_count=groups, preferred_element_type=_F32)


def _conv_fwd(x, w, strides, padding, rhs_dilation, dn, groups):
    return conv_general(x, w, strides, padding, rhs_dilation, dn,
                        groups), (x, w)


def _conv_bwd(strides, padding, rhs_dilation, dn, groups, res, g):
    from jax._src.lax import convolution as _convmod
    x, w = res
    g = g.astype(x.dtype)
    dnums = lax.conv_dimension_numbers(x.shape, w.shape, dn)
    kw = dict(window_strides=strides, padding=padding,
              lhs_dilation=(1,) * len(strides),
              rhs_dilation=rhs_dilation, dimension_numbers=dnums,
              feature_group_count=groups, batch_group_count=1,
              precision=None, preferred_element_type=_F32)
    dx = _convmod._conv_general_dilated_transpose_lhs(g, x, w, **kw)
    dw = _convmod._conv_general_dilated_transpose_rhs(g, x, w, **kw)
    return dx.astype(x.dtype), dw.astype(w.dtype)


conv_general.defvjp(_conv_fwd, _conv_bwd)


# ----------------------------------------------------------------------
# bf16 dot_general with f32 accumulation, both directions.  Without
# this, lax's builtin transpose rule promotes the bf16 operand to match
# the f32 cotangent and the *backward* GEMMs — two thirds of a
# transformer's contraction FLOPs — silently run on f32.  Same shape as
# conv_general: residuals are the bf16 inputs, the cotangent is cast to
# the AMP gradient dtype first, and both transpose dots again request
# f32 accumulation before the edge downcast.
# ----------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def dot_general(lhs, rhs, dnums):
    return lax.dot_general(lhs, rhs, dimension_numbers=dnums,
                           preferred_element_type=_F32)


def _dg_fwd(lhs, rhs, dnums):
    return dot_general(lhs, rhs, dnums), (lhs, rhs)


def _dg_bwd(dnums, res, g):
    from jax._src.lax import lax as _laxmod
    lhs, rhs = res
    g = g.astype(lhs.dtype)
    kw = dict(dimension_numbers=dnums, precision=None,
              preferred_element_type=_F32)
    try:
        dl = _laxmod._dot_general_transpose_lhs(
            g, lhs, rhs, out_type=None, **kw)
        dr = _laxmod._dot_general_transpose_rhs(
            g, lhs, rhs, out_type=None, **kw)
    except TypeError:  # older jax: no out_type kwarg
        dl = _laxmod._dot_general_transpose_lhs(g, lhs, rhs, **kw)
        dr = _laxmod._dot_general_transpose_rhs(g, lhs, rhs, **kw)
    return dl.astype(lhs.dtype), dr.astype(rhs.dtype)


dot_general.defvjp(_dg_fwd, _dg_bwd)


def matmul(a, b):
    """``jnp.matmul`` semantics (ndim >= 2 operands) routed through
    :func:`dot_general` — batch dims broadcast, last axis of ``a``
    contracts with the second-to-last of ``b``."""
    batch = jnp.broadcast_shapes(a.shape[:-2], b.shape[:-2])
    a = jnp.broadcast_to(a, batch + a.shape[-2:])
    b = jnp.broadcast_to(b, batch + b.shape[-2:])
    nb = len(batch)
    dn = (((a.ndim - 1,), (b.ndim - 2,)),
          (tuple(range(nb)), tuple(range(nb))))
    return dot_general(a, b, dn)


# ----------------------------------------------------------------------
# dynamic loss scaler (pure functions; state is threaded through the
# train step and rides save_states/load_states)
# ----------------------------------------------------------------------
def scaler_init(init_scale: Optional[float] = None):
    """(scale f32, good_steps i32, skipped_steps i32)."""
    if init_scale is None:
        init_scale = float(knobs.get("MXTPU_AMP_LOSS_SCALE"))
    return (jnp.asarray(init_scale, jnp.float32),
            jnp.asarray(0, jnp.int32),
            jnp.asarray(0, jnp.int32))


def scaler_update(state, finite, window: Optional[int] = None):
    """Grow x2 after ``window`` consecutive finite steps (capped at
    2^24), halve (floor 1.0) and count a skipped step on non-finite."""
    if window is None:
        window = max(1, int(knobs.get("MXTPU_AMP_SCALE_WINDOW")))
    scale, good, skipped = state
    finite = jnp.asarray(finite, bool)
    good1 = good + 1
    grow = jnp.logical_and(finite, good1 >= window)
    new_scale = jnp.where(
        finite,
        jnp.where(grow, jnp.minimum(scale * 2.0, _SCALE_MAX), scale),
        jnp.maximum(scale * 0.5, 1.0))
    new_good = jnp.where(jnp.logical_and(finite, jnp.logical_not(grow)),
                         good1, jnp.zeros_like(good))
    new_skipped = skipped + jnp.where(finite, 0, 1).astype(skipped.dtype)
    return (new_scale.astype(scale.dtype), new_good.astype(good.dtype),
            new_skipped)


def all_finite(tree) -> Any:
    """Scalar bool: every float leaf of ``tree`` is finite."""
    ok = jnp.asarray(True)
    for leaf in jax.tree_util.tree_leaves(tree):
        if jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.floating):
            ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(leaf)))
    return ok


# ----------------------------------------------------------------------
# self-check (ci_static stage): policy parse + autocast round-trip on
# the selftest program + scaler unit probe
# ----------------------------------------------------------------------
def _check_policy() -> None:
    policy = load_policy()
    allow, deny, force = policy_sets()
    if "dot" not in allow:
        raise MXNetError("amp self-check: policy allow class lost `dot`")
    if not deny or "reduce" not in force:
        raise MXNetError("amp self-check: policy deny/fp32_force empty")
    if allow & (deny | force):
        raise MXNetError("amp self-check: policy classes overlap")
    for cc in ("batch_norm", "flash_attention", "layer_norm"):
        meta = policy.get("custom_calls", {}).get(cc, {})
        if meta.get("accum_dtype") != "f32":
            raise MXNetError(
                f"amp self-check: custom call {cc} lost its f32 "
                f"accumulation contract")


def _check_autocast_roundtrip() -> None:
    import numpy as np
    from .. import nd
    from ..analysis import dtypeflow, lowered_text

    def program(a, b):
        with autocast():
            y = nd.dot(nd.NDArray(a, None, _placed=True),
                       nd.NDArray(b, None, _placed=True))
            z = nd.softmax(y)
        return (z._data.astype(jnp.float32) ** 2).sum()

    a = jnp.asarray(np.linspace(-1, 1, 64, dtype=np.float32).reshape(8, 8))
    b = jnp.asarray(np.linspace(1, -1, 32, dtype=np.float32).reshape(8, 4))
    text = lowered_text(program, a, b)
    ledger = dtypeflow.program_ledger(text)
    hazards = ledger.get("hazards", [])
    if hazards:
        raise MXNetError(
            f"amp self-check: autocast round-trip produced hazards: "
            f"{hazards}")
    if "bf16" not in text:
        raise MXNetError(
            "amp self-check: autocast produced no bf16 edges on the "
            "selftest dot")
    flows = ledger.get("flows", {})
    if not any("f32->bf16" in k or ("f32" in k and "bf16" in k)
               for k in flows):
        raise MXNetError(
            f"amp self-check: no f32->bf16 cast flow recorded "
            f"({sorted(flows)})")
    # kill-switch shape: outside a scope the same program is pure f32
    def program_off(a, b):
        y = nd.dot(nd.NDArray(a, None, _placed=True),
                   nd.NDArray(b, None, _placed=True))
        z = nd.softmax(y)
        return (z._data.astype(jnp.float32) ** 2).sum()
    if "bf16" in lowered_text(program_off, a, b):
        raise MXNetError("amp self-check: bf16 leaked outside autocast")


def _check_scaler() -> None:
    import numpy as np
    upd = jax.jit(functools.partial(scaler_update, window=3))
    st = scaler_init(1024.0)
    for _ in range(3):
        st = upd(st, True)
    if float(st[0]) != 2048.0 or int(st[1]) != 0:
        raise MXNetError(f"amp self-check: scaler grow broken: {st}")
    st = upd(st, False)
    if float(st[0]) != 1024.0 or int(st[2]) != 1:
        raise MXNetError(f"amp self-check: scaler backoff broken: {st}")
    st = upd(st, True)
    if float(st[0]) != 1024.0 or int(st[1]) != 1 or int(st[2]) != 1:
        raise MXNetError(f"amp self-check: scaler resume broken: {st}")
    bad = (np.ones(3, np.float32), np.array([1.0, np.inf], np.float32))
    if bool(all_finite(bad)) or not bool(all_finite(bad[0])):
        raise MXNetError("amp self-check: all_finite broken")


def self_check(verbose: bool = False) -> int:
    """Probe the three AMP contracts; returns 0 on success (raises on
    failure).  Run as a ci_static stage: ``python -m mxtpu.amp
    --self-check``."""
    _check_policy()
    if verbose:
        print("amp self-check: policy parse OK "
              f"({POLICY_PATH})")
    _check_autocast_roundtrip()
    if verbose:
        print("amp self-check: autocast round-trip OK "
              "(bf16 dot, zero hazards, no leak outside the scope)")
    _check_scaler()
    if verbose:
        print("amp self-check: loss-scaler unit probe OK "
              "(grow/backoff/skip accounting)")
    return 0
