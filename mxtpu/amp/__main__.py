"""``python -m mxtpu.amp --self-check`` — the ci_static AMP stage.

Probes the three contracts the AMP pass rests on: the committed
``contracts/amp_policy.json`` parses and keeps its class invariants, an
autocast round-trip on the selftest dot produces bf16 contraction edges
with zero dtype-flow hazards (and no bf16 leak outside the scope), and
the dynamic loss scaler's grow/backoff/skip accounting is exact.
"""
from __future__ import annotations

import argparse
import os
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="python -m mxtpu.amp")
    parser.add_argument("--self-check", action="store_true",
                        help="probe policy parse + autocast round-trip "
                             "+ scaler units")
    args = parser.parse_args(argv)
    if not args.self_check:
        parser.print_help()
        return 2
    # the round-trip lowers a program; stay off any attached accelerator
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from . import self_check
    return self_check(verbose=True)


if __name__ == "__main__":
    sys.exit(main())
