"""RecordIO — binary record container, format-compatible with the
reference so ``im2rec``-produced ``.rec``/``.idx`` datasets load as-is.

Reference: ``python/mxnet/recordio.py``† (pure-python MXRecordIO /
MXIndexedRecordIO over the dmlc-core C codec) and
``3rdparty/dmlc-core/include/dmlc/recordio.h``† (the wire format:
``kMagic = 0xced7230a``; per record a u32 magic, a u32 whose upper 3
bits are the continuation flag and lower 29 bits the payload length,
then the payload padded to a 4-byte boundary).

TPU-native note: the hot path (training input) prefers the C++ codec in
``mxtpu.core`` when built (see ``core/``); this module is the always-
available pure-python implementation and the API surface.
"""
from __future__ import annotations

import numbers
import os
import struct
import threading
from collections import namedtuple
from typing import List, Optional

import numpy as np

from .base import MXNetError

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IRHeader",
           "pack", "unpack", "pack_img", "unpack_img", "scan",
           "read_batch", "read_batch_into", "native_available"]


def _native():
    """The C++ codec (core/recordio_core.cc), if built."""
    global _NATIVE
    if _NATIVE is not None:
        return _NATIVE if _NATIVE is not False else None
    try:
        import mxtpu_core
        _NATIVE = mxtpu_core
    except ImportError:
        import sys
        core_dir = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "core")
        if os.path.isdir(core_dir) and core_dir not in sys.path:
            sys.path.append(core_dir)
            try:
                import mxtpu_core
                _NATIVE = mxtpu_core
            except ImportError:
                _NATIVE = False
        else:
            _NATIVE = False
    return _NATIVE if _NATIVE is not False else None


_NATIVE = None


def native_available() -> bool:
    return _native() is not None


def scan(uri: str):
    """Index every record of a .rec file → (offsets, lengths) — no
    .idx needed.  Native C scanner when built, python fallback."""
    nat = _native()
    if nat is not None:
        return nat.scan(uri)
    offsets, lengths = [], []
    with MXRecordIO(uri, "r") as rec:
        while True:
            pos = rec.tell()
            payload = rec.read()
            if payload is None:
                break
            offsets.append(pos)
            lengths.append(len(payload))
    return offsets, lengths


def read_batch(uri: str, offsets, lengths, n_threads: int = 4):
    """Bulk-read records by (offset, length) — parallel pread in C when
    built (the DataLoader hot path), sequential python otherwise."""
    nat = _native()
    if nat is not None:
        return nat.read_batch(uri, list(offsets), list(lengths),
                              n_threads)
    out = []
    with open(uri, "rb") as f:
        for off in offsets:
            f.seek(off)
            header = f.read(8)
            magic, lrec = struct.unpack("<II", header)
            if magic != _K_MAGIC:
                raise MXNetError(f"invalid magic at offset {off}")
            cflag, length = _decode_lrec(lrec)
            parts = [f.read(length)]
            while cflag not in (0, 3):
                f.seek((4 - (length & 3)) & 3, 1)
                magic, lrec = struct.unpack("<II", f.read(8))
                cflag, length = _decode_lrec(lrec)
                parts.append(f.read(length))
            out.append(b"".join(parts))
    return out

def read_batch_into(uri: str, offsets, lengths, out: np.ndarray,
                    header_bytes: int, n_threads: int = 4) -> bytes:
    """Bulk-read N EQUAL-LENGTH records, splitting each payload into
    its first ``header_bytes`` (returned concatenated, for vectorized
    IRHeader/label parsing) and the remainder, written into row ``i``
    of ``out`` (a writable C-contiguous uint8 array of exactly
    ``N * (length - header_bytes)`` bytes).

    This is the ImageRecordIter raw-record hot path: one call moves a
    whole batch from file to the preallocated batch buffer with record
    framing, header split, and assembly in C (GIL released, parallel
    pread) when the native core is built; the python fallback still
    assembles per batch — one ``b"".join`` + one ``frombuffer`` — not
    per record."""
    nat = _native()
    if nat is not None and hasattr(nat, "read_batch_into"):
        return nat.read_batch_into(uri, list(offsets), list(lengths),
                                   out, header_bytes, n_threads)
    lengths = list(lengths)
    if len(set(lengths)) > 1:
        raise MXNetError("read_batch_into needs equal record lengths")
    raws = read_batch(uri, offsets, lengths, n_threads)
    flat = np.frombuffer(b"".join(raws), np.uint8)
    rows = flat.reshape(len(raws), lengths[0])
    out.reshape(len(raws), -1)[...] = rows[:, header_bytes:]
    return rows[:, :header_bytes].tobytes()


_K_MAGIC = 0xCED7230A
_FLAG_BITS = 29
_LEN_MASK = (1 << _FLAG_BITS) - 1


def _encode_lrec(cflag: int, length: int) -> int:
    return (cflag << _FLAG_BITS) | length


def _decode_lrec(lrec: int):
    return lrec >> _FLAG_BITS, lrec & _LEN_MASK


class MXRecordIO:
    """Sequential RecordIO reader/writer (reference ``MXRecordIO``†).

    Large records are split into continuation chunks exactly as
    dmlc-core does, so files interoperate both directions.
    """

    def __init__(self, uri: str, flag: str):
        self.uri = uri
        self.flag = flag
        self.pid = None
        self.record = None
        self.is_open = False
        self.open()

    def open(self):
        if self.flag == "w":
            self.record = open(self.uri, "wb")
            self.writable = True
        elif self.flag == "r":
            self.record = open(self.uri, "rb")
            self.writable = False
        else:
            raise MXNetError(f"invalid flag {self.flag!r} (use 'r'/'w')")
        self.pid = os.getpid()
        self.is_open = True

    def close(self):
        if self.is_open:
            self.record.close()
            self.is_open = False
            self.pid = None

    def reset(self):
        """Seek back to the beginning (read mode)."""
        self.close()
        self.open()

    def _check_pid(self, allow_reset=False):
        # Reference behavior: a forked DataLoader worker must re-open its
        # own file handle (the descriptor's offset is shared after fork).
        if self.pid != os.getpid():
            if allow_reset:
                self.close()
                self.open()
            else:
                raise MXNetError("RecordIO handle used in a forked "
                                 "process; call reset() first")

    def write(self, buf: bytes):
        # Always written as one complete chunk (cflag 0) — dmlc readers
        # accept that unconditionally; the multi-chunk form (cflags
        # 1/2/3, produced by dmlc writers that split payloads at
        # embedded magic words for seek-recovery) is handled in read().
        assert self.writable
        self._check_pid(allow_reset=False)
        n = len(buf)
        self.record.write(struct.pack("<II", _K_MAGIC,
                                      _encode_lrec(0, n)))
        self.record.write(buf)
        pad = (4 - (n & 3)) & 3
        if pad:
            self.record.write(b"\x00" * pad)

    def read(self) -> Optional[bytes]:
        assert not self.writable
        self._check_pid(allow_reset=True)
        parts: List[bytes] = []
        while True:
            header = self.record.read(8)
            if len(header) < 8:
                return b"".join(parts) if parts else None
            magic, lrec = struct.unpack("<II", header)
            if magic != _K_MAGIC:
                raise MXNetError(
                    f"invalid RecordIO magic {magic:#x} in {self.uri}")
            cflag, length = _decode_lrec(lrec)
            data = self.record.read(length)
            if len(data) < length:
                raise MXNetError(f"truncated record in {self.uri}")
            pad = (4 - (length & 3)) & 3
            if pad:
                self.record.read(pad)
            parts.append(data)
            # cflag: 0 = complete record, 1 = first chunk, 2 = middle,
            # 3 = last chunk (dmlc recordio.h†)
            if cflag in (0, 3):
                return b"".join(parts)

    def tell(self) -> int:
        return self.record.tell()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class MXIndexedRecordIO(MXRecordIO):
    """RecordIO with a ``.idx`` sidecar for random access
    (reference ``MXIndexedRecordIO``†; idx format: ``key\\toffset`` lines)."""

    def __init__(self, idx_path: str, uri: str, flag: str,
                 key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys: List = []
        self.key_type = key_type
        self.fidx = None
        # seek+read must be atomic: DataLoader's thread pool shares one
        # dataset (and thus one file handle) across workers
        self._lock = threading.Lock()
        super().__init__(uri, flag)

    def open(self):
        super().open()
        self.idx = {}
        self.keys = []
        if self.writable:
            self.fidx = open(self.idx_path, "w")
        else:
            self.fidx = None
            if os.path.exists(self.idx_path):
                with open(self.idx_path) as f:
                    for line in f:
                        parts = line.strip().split("\t")
                        if len(parts) < 2:
                            continue
                        key = self.key_type(parts[0])
                        self.idx[key] = int(parts[1])
                        self.keys.append(key)
            else:
                # no .idx sidecar: rebuild the index by scanning the
                # record chain (C-speed when the native core is built);
                # cached on the instance so reset()/post-fork reopen
                # don't rescan the whole file
                cached = getattr(self, "_scan_cache", None)
                if cached is None:
                    cached, _ = scan(self.uri)
                    self._scan_cache = cached
                for i, off in enumerate(cached):
                    key = self.key_type(i)
                    self.idx[key] = off
                    self.keys.append(key)

    def close(self):
        if self.is_open and self.fidx is not None:
            self.fidx.close()
            self.fidx = None
        super().close()

    def seek(self, idx):
        assert not self.writable
        self._check_pid(allow_reset=True)
        self.record.seek(self.idx[idx])

    def read_idx(self, idx) -> bytes:
        with self._lock:
            self.seek(idx)
            return self.read()

    def write_idx(self, idx, buf: bytes):
        assert self.writable
        key = self.key_type(idx)
        pos = self.tell()
        self.write(buf)
        self.fidx.write(f"{key}\t{pos}\n")
        self.idx[key] = pos
        self.keys.append(key)


#: Image-record header (reference ``IRHeader``†): flag counts extra float
#: labels; label is a scalar when flag == 0.
IRHeader = namedtuple("HEADER", ["flag", "label", "id", "id2"])
_IR_FORMAT = "<IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def pack(header: IRHeader, s: bytes) -> bytes:
    """Pack a header + payload into the image-record wire format
    (reference ``pack``†)."""
    header = IRHeader(*header)
    if isinstance(header.label, numbers.Number):
        out = struct.pack(_IR_FORMAT, header.flag, header.label,
                          header.id, header.id2)
    else:
        label = np.asarray(header.label, dtype=np.float32)
        out = struct.pack(_IR_FORMAT, label.size, 0.0, header.id,
                          header.id2)
        out += label.tobytes()
    return out + s


def unpack(s: bytes):
    """Unpack ``pack`` output → (IRHeader, payload) (reference†)."""
    header = IRHeader(*struct.unpack(_IR_FORMAT, s[:_IR_SIZE]))
    s = s[_IR_SIZE:]
    if header.flag > 0:
        label = np.frombuffer(s[:header.flag * 4], np.float32).copy()
        header = header._replace(label=label)
        s = s[header.flag * 4:]
    return header, s


def pack_img(header: IRHeader, img, quality=95, img_fmt=".jpg") -> bytes:
    """Encode an image (HWC uint8 numpy array) and pack it
    (reference ``pack_img``†, OpenCV-backed)."""
    import cv2
    ext = img_fmt.lower()
    if ext in (".jpg", ".jpeg"):
        encode_params = [cv2.IMWRITE_JPEG_QUALITY, quality]
    elif ext == ".png":
        encode_params = [cv2.IMWRITE_PNG_COMPRESSION, quality // 10]
    else:
        raise MXNetError(f"unsupported image format {img_fmt}")
    ret, buf = cv2.imencode(img_fmt, img, encode_params)
    if not ret:
        raise MXNetError("failed to encode image")
    return pack(header, buf.tobytes())


def unpack_img(s: bytes, iscolor=-1):
    """Unpack and decode an image record → (IRHeader, HWC array)
    (reference ``unpack_img``†)."""
    import cv2
    header, payload = unpack(s)
    img = cv2.imdecode(np.frombuffer(payload, np.uint8), iscolor)
    return header, img
